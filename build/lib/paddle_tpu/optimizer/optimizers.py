"""The optimizer zoo.

Reference: `python/paddle/optimizer/{sgd,momentum,adagrad,adam,adamw,adamax,
rmsprop,adadelta,lamb}.py`. Update rules match the reference's kernels
(`paddle/phi/kernels/*_kernel.h` semantics); all math is pure jnp so each
``step`` compiles into the train-step XLA computation.
"""

from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer

__all__ = ["SGD", "Momentum", "Adagrad", "Adam", "AdamW", "Adamax",
           "RMSProp", "Adadelta", "Lamb"]


class SGD(Optimizer):
    def _single_update(self, p, g, lr, value):
        return value - jnp.asarray(lr, value.dtype) * g.astype(value.dtype)


class Momentum(Optimizer):
    """Reference: `python/paddle/optimizer/momentum.py` (velocity form)."""

    _accum_names = ("velocity",)

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _single_update(self, p, g, lr, value):
        v = self._get_accumulator("velocity", p)._data
        g = g.astype(v.dtype)
        mu = jnp.asarray(self._momentum, v.dtype)
        v_new = mu * v + g
        self._set_accumulator("velocity", p, v_new)
        lr = jnp.asarray(lr, value.dtype)
        if self._use_nesterov:
            return value - lr * (g + mu * v_new).astype(value.dtype)
        return value - lr * v_new.astype(value.dtype)


class Adagrad(Optimizer):
    _accum_names = ("moment",)

    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._epsilon = epsilon
        self._initial_accumulator_value = initial_accumulator_value

    def _create_accumulators(self, params):
        for p in params:
            self._add_accumulator("moment", p,
                                  fill_value=self._initial_accumulator_value)

    def _single_update(self, p, g, lr, value):
        m = self._get_accumulator("moment", p)._data
        g = g.astype(m.dtype)
        m_new = m + g * g
        self._set_accumulator("moment", p, m_new)
        upd = g / (jnp.sqrt(m_new) + self._epsilon)
        return value - jnp.asarray(lr, value.dtype) * upd.astype(value.dtype)


class Adam(Optimizer):
    """Reference: `python/paddle/optimizer/adam.py` — bias-corrected via
    beta-power accumulators, exactly the phi adam kernel recurrence."""

    _accum_names = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, amsgrad=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._amsgrad = amsgrad

    def _create_accumulators(self, params):
        for p in params:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            if self._amsgrad:
                self._add_accumulator("moment2_max", p)
            self._add_accumulator("beta1_pow_acc", p, dtype="float32",
                                  fill_value=1.0, shape=())
            self._add_accumulator("beta2_pow_acc", p, dtype="float32",
                                  fill_value=1.0, shape=())

    def _adam_moments(self, p, g):
        m = self._get_accumulator("moment1", p)._data
        v = self._get_accumulator("moment2", p)._data
        b1p = self._get_accumulator("beta1_pow_acc", p)._data * self._beta1
        b2p = self._get_accumulator("beta2_pow_acc", p)._data * self._beta2
        g = g.astype(m.dtype)
        m_new = self._beta1 * m + (1 - self._beta1) * g
        v_new = self._beta2 * v + (1 - self._beta2) * g * g
        self._set_accumulator("moment1", p, m_new)
        self._set_accumulator("moment2", p, v_new)
        self._set_accumulator("beta1_pow_acc", p, b1p)
        self._set_accumulator("beta2_pow_acc", p, b2p)
        if self._amsgrad:
            v_max = jnp.maximum(
                self._get_accumulator("moment2_max", p)._data, v_new)
            self._set_accumulator("moment2_max", p, v_max)
            v_new = v_max
        return m_new, v_new, b1p, b2p

    def _single_update(self, p, g, lr, value):
        m_new, v_new, b1p, b2p = self._adam_moments(p, g)
        lr_t = jnp.asarray(lr, jnp.float32) * jnp.sqrt(1 - b2p) / (1 - b1p)
        # epsilon scales with sqrt(1-beta2^t) exactly like the reference phi
        # kernel (adam_functors.h:225): m / (sqrt(v) + eps*sqrt(1-beta2_pow))
        upd = m_new / (jnp.sqrt(v_new)
                       + self._epsilon * jnp.sqrt(1 - b2p))
        return value - (lr_t.astype(value.dtype)
                        * upd.astype(value.dtype))


class AdamW(Adam):
    """Decoupled weight decay (reference `adamw.py:40`): decay applies to the
    parameter directly, not through the gradient."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, amsgrad=False,
                 name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision,
                         amsgrad=amsgrad, name=name)
        self._coeff = float(weight_decay) if not hasattr(weight_decay, "coeff") \
            else weight_decay.coeff
        self._lr_ratio = lr_ratio
        self._apply_decay_param_fun = apply_decay_param_fun

    def _apply_regularization(self, p, g):
        return g  # decay is decoupled

    def _single_update(self, p, g, lr, value):
        if self._lr_ratio is not None:
            lr = lr * self._lr_ratio(p)
        with_decay = True
        if self._apply_decay_param_fun is not None and \
                not self._apply_decay_param_fun(p.name):
            with_decay = False
        coeff = self._coeff
        if self._group_weight_decay is not None:
            gw = self._group_weight_decay
            coeff = float(getattr(gw, "coeff", gw))
        if with_decay and coeff != 0.0:
            value = value * (1.0 - jnp.asarray(lr, jnp.float32)
                             * coeff).astype(value.dtype)
        return super()._single_update(p, g, lr, value)


class Adamax(Optimizer):
    _accum_names = ("moment", "inf_norm")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, params):
        for p in params:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow_acc", p, dtype="float32",
                                  fill_value=1.0, shape=())

    def _single_update(self, p, g, lr, value):
        m = self._get_accumulator("moment", p)._data
        u = self._get_accumulator("inf_norm", p)._data
        b1p = self._get_accumulator("beta1_pow_acc", p)._data * self._beta1
        g = g.astype(m.dtype)
        m_new = self._beta1 * m + (1 - self._beta1) * g
        u_new = jnp.maximum(self._beta2 * u, jnp.abs(g) + self._epsilon)
        self._set_accumulator("moment", p, m_new)
        self._set_accumulator("inf_norm", p, u_new)
        self._set_accumulator("beta1_pow_acc", p, b1p)
        lr_t = jnp.asarray(lr, jnp.float32) / (1 - b1p)
        return value - (lr_t * (m_new / u_new)).astype(value.dtype)


class RMSProp(Optimizer):
    _accum_names = ("mean_square", "mean_grad", "momentum_acc")

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _single_update(self, p, g, lr, value):
        ms = self._get_accumulator("mean_square", p)._data
        mom = self._get_accumulator("momentum_acc", p)._data
        g = g.astype(ms.dtype)
        ms_new = self._rho * ms + (1 - self._rho) * g * g
        self._set_accumulator("mean_square", p, ms_new)
        denom = ms_new
        if self._centered:
            mg = self._get_accumulator("mean_grad", p)._data
            mg_new = self._rho * mg + (1 - self._rho) * g
            self._set_accumulator("mean_grad", p, mg_new)
            denom = ms_new - mg_new * mg_new
        lr = jnp.asarray(lr, ms.dtype)
        mom_new = self._momentum * mom + lr * g / jnp.sqrt(
            denom + self._epsilon)
        self._set_accumulator("momentum_acc", p, mom_new)
        return value - mom_new.astype(value.dtype)


class Adadelta(Optimizer):
    _accum_names = ("avg_squared_grad", "avg_squared_update")

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._epsilon = epsilon
        self._rho = rho

    def _single_update(self, p, g, lr, value):
        ag = self._get_accumulator("avg_squared_grad", p)._data
        au = self._get_accumulator("avg_squared_update", p)._data
        g = g.astype(ag.dtype)
        ag_new = self._rho * ag + (1 - self._rho) * g * g
        upd = jnp.sqrt(au + self._epsilon) / jnp.sqrt(
            ag_new + self._epsilon) * g
        au_new = self._rho * au + (1 - self._rho) * upd * upd
        self._set_accumulator("avg_squared_grad", p, ag_new)
        self._set_accumulator("avg_squared_update", p, au_new)
        return value - jnp.asarray(lr, value.dtype) * upd.astype(value.dtype)


class Lamb(Optimizer):
    """Layer-wise adaptive moments (reference `python/paddle/optimizer/lamb.py`)."""

    _accum_names = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._lamb_weight_decay = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _create_accumulators(self, params):
        for p in params:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow_acc", p, dtype="float32",
                                  fill_value=1.0, shape=())
            self._add_accumulator("beta2_pow_acc", p, dtype="float32",
                                  fill_value=1.0, shape=())

    def _single_update(self, p, g, lr, value):
        m = self._get_accumulator("moment1", p)._data
        v = self._get_accumulator("moment2", p)._data
        b1p = self._get_accumulator("beta1_pow_acc", p)._data * self._beta1
        b2p = self._get_accumulator("beta2_pow_acc", p)._data * self._beta2
        g = g.astype(jnp.float32)
        m_new = self._beta1 * m + (1 - self._beta1) * g
        v_new = self._beta2 * v + (1 - self._beta2) * g * g
        self._set_accumulator("moment1", p, m_new)
        self._set_accumulator("moment2", p, v_new)
        self._set_accumulator("beta1_pow_acc", p, b1p)
        self._set_accumulator("beta2_pow_acc", p, b2p)
        m_hat = m_new / (1 - b1p)
        v_hat = v_new / (1 - b2p)
        wd = self._lamb_weight_decay
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        val32 = value.astype(jnp.float32)
        r = m_hat / (jnp.sqrt(v_hat) + self._epsilon) + wd * val32
        w_norm = jnp.linalg.norm(val32)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return (val32 - jnp.asarray(lr, jnp.float32) * trust * r).astype(
            value.dtype)
