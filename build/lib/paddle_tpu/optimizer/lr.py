"""Learning-rate schedules.

Reference: `python/paddle/optimizer/lr.py` (LRScheduler family, ~20
schedules). TPU-native note: schedules are host-side Python state — the
current lr is fed into the compiled train step as a scalar input, so
changing lr never retraces (see ``paddle_tpu.jit``).
"""

from __future__ import annotations

import math

__all__ = [
    "LRScheduler", "NoamDecay", "PiecewiseDecay", "NaturalExpDecay",
    "InverseTimeDecay", "PolynomialDecay", "LinearWarmup", "ExponentialDecay",
    "MultiStepDecay", "StepDecay", "LambdaDecay", "ReduceOnPlateau",
    "CosineAnnealingDecay", "MultiplicativeDecay", "OneCycleLR", "CyclicLR",
    "LinearLR", "CosineAnnealingWarmRestarts",
]


class LRScheduler:
    """Base class (reference lr.py ``LRScheduler``): subclasses implement
    ``get_lr()``; ``step()`` advances ``last_epoch`` and refreshes
    ``last_lr``."""

    def __init__(self, learning_rate=0.1, last_epoch=-1, verbose=False):
        if not isinstance(learning_rate, (float, int)):
            raise TypeError(
                f"learning_rate must be float, got {type(learning_rate)}")
        self.base_lr = float(learning_rate)
        self.last_epoch = last_epoch
        self.verbose = verbose
        self.last_lr = self.base_lr
        self.step()

    def __call__(self):
        return self.last_lr

    def step(self, epoch=None):
        if epoch is None:
            self.last_epoch += 1
        else:
            self.last_epoch = epoch
        self.last_lr = self.get_lr()
        if self.verbose:
            print(f"Epoch {self.last_epoch}: {type(self).__name__} "
                  f"set learning rate to {self.last_lr}.")

    def get_lr(self):
        raise NotImplementedError

    def state_dict(self):
        """Host-side schedule state (reference lr.py state_dict): every
        non-callable instance attribute."""
        state = {}
        for k, v in self.__dict__.items():
            if k == "verbose" or callable(v):
                continue
            state[k] = v
        return state

    def set_state_dict(self, state_dict):
        for k, v in state_dict.items():
            if k in self.__dict__:
                self.__dict__[k] = v
        self.last_lr = self.get_lr()

    set_dict = set_state_dict


class NoamDecay(LRScheduler):
    """lr = d_model^-0.5 * min(step^-0.5, step * warmup^-1.5)."""

    def __init__(self, d_model, warmup_steps, learning_rate=1.0,
                 last_epoch=-1, verbose=False):
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 1)
        a = step ** -0.5
        b = step * (self.warmup_steps ** -1.5)
        return self.base_lr * (self.d_model ** -0.5) * min(a, b)


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries, values, last_epoch=-1, verbose=False):
        if len(values) != len(boundaries) + 1:
            raise ValueError("values must have one more element than boundaries")
        self.boundaries = list(boundaries)
        self.values = [float(v) for v in values]
        super().__init__(self.values[0], last_epoch, verbose)

    def get_lr(self):
        for i, b in enumerate(self.boundaries):
            if self.last_epoch < b:
                return self.values[i]
        return self.values[-1]


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * math.exp(-self.gamma * self.last_epoch)


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr / (1 + self.gamma * self.last_epoch)


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0,
                 cycle=False, last_epoch=-1, verbose=False):
        self.decay_steps = decay_steps
        self.end_lr = end_lr
        self.power = power
        self.cycle = cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = self.last_epoch
        decay_steps = self.decay_steps
        if self.cycle:
            div = math.ceil(step / decay_steps) if step > 0 else 1
            decay_steps = decay_steps * div
        else:
            step = min(step, decay_steps)
        frac = (1 - step / decay_steps) ** self.power
        return (self.base_lr - self.end_lr) * frac + self.end_lr


class LinearWarmup(LRScheduler):
    """Linear warmup from ``start_lr`` to ``end_lr`` over ``warmup_steps``,
    then follow the wrapped schedule (or constant)."""

    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr,
                 last_epoch=-1, verbose=False):
        self.learning_rate = learning_rate  # float or LRScheduler
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr
        super().__init__(start_lr, last_epoch, verbose)

    def get_lr(self):
        if self.last_epoch < self.warmup_steps:
            if self.warmup_steps == 0:
                return self.end_lr
            return (self.end_lr - self.start_lr) * (
                self.last_epoch / self.warmup_steps) + self.start_lr
        if isinstance(self.learning_rate, LRScheduler):
            self.learning_rate.step(self.last_epoch - self.warmup_steps)
            return self.learning_rate()
        return float(self.learning_rate)

    def state_dict(self):
        state = super().state_dict()
        inner = state.pop("learning_rate", None)
        if isinstance(inner, LRScheduler):
            state["LinearWarmup_LR"] = inner.state_dict()
        return state

    def set_state_dict(self, state_dict):
        inner = state_dict.pop("LinearWarmup_LR", None)
        if inner is not None and isinstance(self.learning_rate, LRScheduler):
            self.learning_rate.set_state_dict(inner)
        super().set_state_dict(state_dict)


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * (self.gamma ** self.last_epoch)


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1,
                 verbose=False):
        if not all(milestones[i] < milestones[i + 1]
                   for i in range(len(milestones) - 1)):
            raise ValueError("milestones must be increasing")
        self.milestones = list(milestones)
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        n = sum(1 for m in self.milestones if m <= self.last_epoch)
        return self.base_lr * (self.gamma ** n)


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1,
                 verbose=False):
        if step_size <= 0:
            raise ValueError("step_size must be positive")
        self.step_size = step_size
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * (self.gamma ** (self.last_epoch // self.step_size))


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.lr_lambda(self.last_epoch)


class MultiplicativeDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        cur = self.base_lr
        for e in range(1, self.last_epoch + 1):
            cur *= self.lr_lambda(e)
        return cur


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0, last_epoch=-1,
                 verbose=False):
        self.T_max = T_max
        self.eta_min = float(eta_min)
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.eta_min + (self.base_lr - self.eta_min) * (
            1 + math.cos(math.pi * self.last_epoch / self.T_max)) / 2


class CosineAnnealingWarmRestarts(LRScheduler):
    def __init__(self, learning_rate, T_0, T_mult=1, eta_min=0, last_epoch=-1,
                 verbose=False):
        if T_0 <= 0 or T_mult < 1:
            raise ValueError("T_0 must be > 0 and T_mult >= 1")
        self.T_0 = T_0
        self.T_mult = T_mult
        self.eta_min = float(eta_min)
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        epoch = max(self.last_epoch, 0)
        t_i = self.T_0
        t_cur = epoch
        while t_cur >= t_i:
            t_cur -= t_i
            t_i *= self.T_mult
        return self.eta_min + (self.base_lr - self.eta_min) * (
            1 + math.cos(math.pi * t_cur / t_i)) / 2


class ReduceOnPlateau(LRScheduler):
    """Reduce lr when a metric has stopped improving (reference lr.py
    ``ReduceOnPlateau``); ``step(metric)`` takes the monitored value."""

    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10,
                 threshold=1e-4, threshold_mode="rel", cooldown=0, min_lr=0,
                 epsilon=1e-8, verbose=False):
        if mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")
        if threshold_mode not in ("rel", "abs"):
            raise ValueError("threshold_mode must be 'rel' or 'abs'")
        if factor >= 1.0:
            raise ValueError("factor must be < 1.0")
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.epsilon = epsilon
        self.verbose = verbose
        self.base_lr = float(learning_rate)
        self.last_lr = float(learning_rate)
        self.cooldown_counter = 0
        self.best = None
        self.num_bad_epochs = 0
        self.last_epoch = 0

    def step(self, metrics, epoch=None):
        if epoch is None:
            self.last_epoch += 1
        else:
            self.last_epoch = epoch
        try:
            current = float(metrics)
        except (TypeError, ValueError):
            current = float(getattr(metrics, "item")())
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
        else:
            if self.best is None or self._is_better(current):
                self.best = current
                self.num_bad_epochs = 0
            else:
                self.num_bad_epochs += 1
            if self.num_bad_epochs > self.patience:
                self.cooldown_counter = self.cooldown
                self.num_bad_epochs = 0
                new_lr = max(self.last_lr * self.factor, self.min_lr)
                if self.last_lr - new_lr > self.epsilon:
                    self.last_lr = new_lr
                    if self.verbose:
                        print(f"Epoch {self.last_epoch}: ReduceOnPlateau "
                              f"set learning rate to {self.last_lr}.")

    def _is_better(self, current):
        best = self.best
        if self.mode == "min":
            thr = best - self.threshold * abs(best) \
                if self.threshold_mode == "rel" else best - self.threshold
            return current < thr
        thr = best + self.threshold * abs(best) \
            if self.threshold_mode == "rel" else best + self.threshold
        return current > thr

    def get_lr(self):
        return self.last_lr


class OneCycleLR(LRScheduler):
    def __init__(self, max_learning_rate, total_steps, divide_factor=25.0,
                 end_learning_rate=0.0001, phase_pct=0.3,
                 anneal_strategy="cos", three_phase=False, last_epoch=-1,
                 verbose=False):
        self.max_lr = float(max_learning_rate)
        self.total_steps = total_steps
        self.initial_lr = self.max_lr / divide_factor
        self.end_lr = float(end_learning_rate)
        self.three_phase = three_phase
        if anneal_strategy not in ("cos", "linear"):
            raise ValueError("anneal_strategy must be 'cos' or 'linear'")
        self.anneal_strategy = anneal_strategy
        up = float(phase_pct * total_steps) - 1
        if three_phase:
            self._phases = [
                (up, self.initial_lr, self.max_lr),
                (2 * up, self.max_lr, self.initial_lr),
                (total_steps - 1, self.initial_lr, self.end_lr),
            ]
        else:
            self._phases = [
                (up, self.initial_lr, self.max_lr),
                (total_steps - 1, self.max_lr, self.end_lr),
            ]
        super().__init__(self.initial_lr, last_epoch, verbose)

    def _anneal(self, start, end, pct):
        if self.anneal_strategy == "cos":
            return end + (start - end) / 2.0 * (math.cos(math.pi * pct) + 1)
        return (end - start) * pct + start

    def get_lr(self):
        step = self.last_epoch
        start_step = 0.0
        for end_step, start_lr, end_lr in self._phases:
            if step <= end_step or end_step == self._phases[-1][0]:
                span = end_step - start_step
                pct = 0.0 if span == 0 else min((step - start_step) / span, 1.0)
                return self._anneal(start_lr, end_lr, pct)
            start_step = end_step
        return self.end_lr


class CyclicLR(LRScheduler):
    def __init__(self, base_learning_rate, max_learning_rate, step_size_up,
                 step_size_down=None, mode="triangular", exp_gamma=1.0,
                 scale_fn=None, scale_mode="cycle", last_epoch=-1,
                 verbose=False):
        self.max_lr = float(max_learning_rate)
        self.step_size_up = step_size_up
        self.step_size_down = step_size_down or step_size_up
        self.cycle_size = self.step_size_up + self.step_size_down
        self.exp_gamma = exp_gamma
        self.mode = mode
        if scale_fn is not None:
            self._scale_fn = scale_fn
            self.scale_mode = scale_mode
        elif mode == "triangular":
            self._scale_fn = lambda x: 1.0
            self.scale_mode = "cycle"
        elif mode == "triangular2":
            self._scale_fn = lambda x: 1 / (2.0 ** (x - 1))
            self.scale_mode = "cycle"
        elif mode == "exp_range":
            self._scale_fn = lambda x: self.exp_gamma ** x
            self.scale_mode = "iterations"
        else:
            raise ValueError(f"invalid mode {mode!r}")
        super().__init__(base_learning_rate, last_epoch, verbose)

    def get_lr(self):
        it = self.last_epoch
        cycle = math.floor(1 + it / self.cycle_size)
        pos = it - (cycle - 1) * self.cycle_size
        if pos <= self.step_size_up:
            pct = pos / self.step_size_up
        else:
            pct = 1 - (pos - self.step_size_up) / self.step_size_down
        amp = (self.max_lr - self.base_lr) * pct
        x = cycle if self.scale_mode == "cycle" else it
        return self.base_lr + amp * self._scale_fn(x)

    def state_dict(self):
        state = super().state_dict()
        state.pop("_scale_fn", None)
        return state


class LinearLR(LRScheduler):
    def __init__(self, learning_rate, total_steps, start_factor=1.0 / 3,
                 end_factor=1.0, last_epoch=-1, verbose=False):
        self.total_steps = total_steps
        self.start_factor = start_factor
        self.end_factor = end_factor
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = min(self.last_epoch, self.total_steps)
        factor = self.start_factor + (
            self.end_factor - self.start_factor) * step / self.total_steps
        return self.base_lr * factor
