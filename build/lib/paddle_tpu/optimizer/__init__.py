"""``paddle_tpu.optimizer`` — optimizers + LR schedules.

Reference: `python/paddle/optimizer/__init__.py`.
"""

from .optimizer import Optimizer  # noqa: F401
from .optimizers import (  # noqa: F401
    SGD, Momentum, Adagrad, Adam, AdamW, Adamax, RMSProp, Adadelta, Lamb,
)
from . import lr  # noqa: F401

__all__ = ["Optimizer", "SGD", "Momentum", "Adagrad", "Adam", "AdamW",
           "Adamax", "RMSProp", "Adadelta", "Lamb", "lr"]
