"""Global runtime flag registry.

TPU-native analog of the reference's exported-flag system
(`paddle/common/flags.h:38`, `paddle/common/flags.cc` — 146 `PHI_DEFINE_EXPORTED_*`
definitions, surfaced in Python as ``paddle.set_flags`` / ``paddle.get_flags``).
Flags are plain Python values; each flag may also be seeded from an environment
variable ``FLAGS_<name>`` at definition time, matching the reference's behavior.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable

__all__ = ["define_flag", "set_flags", "get_flags", "flag"]

_lock = threading.Lock()


class _Flag:
    __slots__ = ("name", "value", "default", "help", "type", "on_change")

    def __init__(self, name, default, help_str, typ, on_change=None):
        self.name = name
        self.default = default
        self.help = help_str
        self.type = typ
        self.on_change = on_change
        self.value = self._from_env(default)

    def _from_env(self, default):
        env = os.environ.get("FLAGS_" + self.name)
        if env is None:
            return default
        return _parse(env, self.type)


def _parse(text: str, typ: type) -> Any:
    if typ is bool:
        return text.lower() in ("1", "true", "yes", "on")
    if typ is int:
        return int(text)
    if typ is float:
        return float(text)
    return text


_REGISTRY: dict[str, _Flag] = {}


def define_flag(name: str, default: Any, help_str: str = "",
                on_change: Callable[[Any], None] | None = None) -> None:
    """Register a runtime flag (analog of ``PHI_DEFINE_EXPORTED_*``)."""
    with _lock:
        if name in _REGISTRY:
            raise KeyError(f"flag '{name}' already defined")
        _REGISTRY[name] = _Flag(name, default, help_str, type(default), on_change)


def set_flags(flags: dict[str, Any]) -> None:
    """Set one or more flags (``paddle.set_flags`` equivalent)."""
    with _lock:
        for name, value in flags.items():
            key = name[6:] if name.startswith("FLAGS_") else name
            if key not in _REGISTRY:
                raise KeyError(f"unknown flag '{name}'")
            f = _REGISTRY[key]
            if isinstance(value, str) and f.type is not str:
                value = _parse(value, f.type)
            f.value = value
            if f.on_change is not None:
                f.on_change(value)


def get_flags(flags: list[str] | str | None = None) -> dict[str, Any]:
    """Read flags (``paddle.get_flags`` equivalent)."""
    if flags is None:
        names = list(_REGISTRY)
    elif isinstance(flags, str):
        names = [flags]
    else:
        names = list(flags)
    out = {}
    for name in names:
        key = name[6:] if name.startswith("FLAGS_") else name
        if key not in _REGISTRY:
            raise KeyError(f"unknown flag '{name}'")
        out["FLAGS_" + key] = _REGISTRY[key].value
    return out


def flag(name: str) -> Any:
    """Fast internal accessor for a single flag value."""
    return _REGISTRY[name].value


# ---------------------------------------------------------------------------
# Core flags (subset of the reference's most load-bearing knobs,
# common/flags.cc). More are defined where their subsystem lives.
# ---------------------------------------------------------------------------
define_flag("check_nan_inf", False,
            "Check outputs of every op for NaN/Inf in eager mode "
            "(reference: FLAGS_check_nan_inf).")
define_flag("benchmark", False, "Synchronize after each op for timing.")
define_flag("low_precision_op_list", 0,
            "Report ops executed in low precision under AMP.")
define_flag("use_pallas_kernels", True,
            "Use Pallas TPU kernels for fused ops (flash attention, rms_norm, "
            "rope) where available; falls back to XLA lowering otherwise.")
define_flag("comm_timeout_seconds", 1800,
            "Collective watchdog timeout (reference: NCCL comm watchdog, "
            "phi/core/distributed/comm_task.h:127).")
define_flag("eager_delete_tensor_gb", 0.0, "Compat no-op: XLA manages memory.")
define_flag("allocator_strategy", "auto_growth",
            "Compat: allocator strategy label (XLA/PJRT owns allocation).")
