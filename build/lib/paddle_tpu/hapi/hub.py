"""``paddle.hub`` (reference: `python/paddle/hapi/hub.py` —
list/help/load entrypoints from a repo's ``hubconf.py``).

Zero-egress build: the ``local`` source (a directory containing
``hubconf.py``) is fully supported; ``github``/``gitee`` sources raise
with a clear message instead of attempting a download. Entrypoint
semantics match the reference: every public callable in hubconf is an
entrypoint; ``dependencies`` is an optional list checked before load.
"""

from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

MODULE_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir, source):
    if source not in ("local",):
        raise RuntimeError(
            f"source={source!r} requires network access; this build "
            "supports source='local' (a directory containing hubconf.py)")
    path = os.path.join(repo_dir, MODULE_HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {MODULE_HUBCONF} in {repo_dir!r}")
    name = f"paddle_tpu_hubconf_{abs(hash(os.path.abspath(path)))}"
    spec = importlib.util.spec_from_file_location(name, path)
    module = importlib.util.module_from_spec(spec)
    sys.modules[name] = module
    try:
        spec.loader.exec_module(module)
    except Exception:
        sys.modules.pop(name, None)
        raise
    deps = getattr(module, "dependencies", [])
    missing = []
    for d in deps:
        try:
            importlib.import_module(d)
        except ImportError:
            missing.append(d)
    if missing:
        raise RuntimeError(
            f"hub repo {repo_dir!r} requires missing packages: {missing}")
    return module


def _entrypoints(module):
    return {n: fn for n, fn in vars(module).items()
            if callable(fn) and not n.startswith("_")}


def list(repo_dir, source="local", force_reload=False):
    """Entrypoint names exposed by the repo's hubconf (reference
    `hub.py:172`)."""
    return sorted(_entrypoints(_load_hubconf(repo_dir, source)))


def help(repo_dir, model, source="local", force_reload=False):
    """Docstring of one entrypoint (reference `hub.py:help`)."""
    eps = _entrypoints(_load_hubconf(repo_dir, source))
    if model not in eps:
        raise RuntimeError(f"cannot find callable {model!r} in hubconf")
    return eps[model].__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    """Build one entrypoint (reference `hub.py:261`)."""
    eps = _entrypoints(_load_hubconf(repo_dir, source))
    if model not in eps:
        raise RuntimeError(f"cannot find callable {model!r} in hubconf")
    return eps[model](**kwargs)
