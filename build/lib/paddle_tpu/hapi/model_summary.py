"""``paddle.summary`` / ``paddle.flops`` (reference:
`python/paddle/hapi/model_summary.py`, `hapi/dynamic_flops.py`).

Both run one forward pass with forward-post hooks on every leaf layer,
collecting output shapes / parameter counts (summary) and applying
per-layer-type FLOP rules (flops). Layer-type coverage mirrors the
reference's `register_hooks` table: conv, linear, norms, pooling,
activations (zero-cost entries count as 0 but still print).
"""

from __future__ import annotations

import numpy as np

from .. import nn
from ..framework.tensor import Tensor

__all__ = ["summary", "flops"]


def _num_params(layer):
    return sum(int(np.prod(p.shape))
               for p in layer.parameters(include_sublayers=False))


def _run_with_hooks(net, input_size, dtype, per_layer):
    """Forward random input through net with a post-hook on each leaf
    layer calling ``per_layer(layer, name, inputs, outputs)``."""
    if isinstance(input_size, (list, tuple)) and input_size \
            and isinstance(input_size[0], (list, tuple)):
        shapes = list(input_size)
    else:
        shapes = [tuple(input_size)]
    xs = [Tensor(np.zeros(s, dtype or "float32")) for s in shapes]
    removes = []
    try:
        for name, sub in net.named_sublayers(include_self=False):
            if list(sub.children()):
                continue  # hook leaves only

            def hook(layer, inputs, outputs, _name=name):
                per_layer(layer, _name, inputs, outputs)

            removes.append(sub.register_forward_post_hook(hook))
        was_training = net.training
        net.eval()
        try:
            net(*xs)
        finally:
            if was_training:
                net.train()
    finally:
        for r in removes:
            r.remove()


def summary(net, input_size, dtypes=None, input=None):
    """Print a per-layer table (type, output shape, params); returns
    ``{'total_params': ..., 'trainable_params': ...}``."""
    rows = []

    def per_layer(layer, name, inputs, outputs):
        out = outputs[0] if isinstance(outputs, (list, tuple)) \
            else outputs
        shape = list(out.shape) if hasattr(out, "shape") else "-"
        rows.append((f"{type(layer).__name__}-{len(rows) + 1}",
                     str(shape), _num_params(layer)))

    if input is not None:
        raise NotImplementedError(
            "summary(input=...) is not supported; pass input_size")
    _run_with_hooks(net, input_size, dtypes, per_layer)

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if p.trainable)
    w1 = max([len(r[0]) for r in rows] + [12])
    w2 = max([len(r[1]) for r in rows] + [14])
    sep = "-" * (w1 + w2 + 14)
    print(sep)
    print(f"{'Layer (type)':<{w1}}  {'Output Shape':<{w2}}  {'Params':>10}")
    print("=" * (w1 + w2 + 14))
    for r in rows:
        print(f"{r[0]:<{w1}}  {r[1]:<{w2}}  {r[2]:>10,}")
    print(sep)
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print(sep)
    return {"total_params": total, "trainable_params": trainable}


def _conv_flops(layer, inputs, out):
    # MACs = out_elems * (Cin/groups) * prod(kernel); FLOPs = 2 * MACs
    k = np.prod(layer._kernel_size) if hasattr(layer, "_kernel_size") \
        else np.prod(layer.weight.shape[2:])
    cin = layer.weight.shape[1]  # already Cin/groups in the weight
    out_elems = int(np.prod(out.shape))
    return 2 * out_elems * int(cin) * int(k)


def _linear_flops(layer, inputs, out):
    in_f, out_f = layer.weight.shape
    batch = int(np.prod(out.shape)) // int(out_f)
    return 2 * batch * int(in_f) * int(out_f)


def _norm_flops(layer, inputs, out):
    return 2 * int(np.prod(out.shape))


def _pool_flops(layer, inputs, out):
    return int(np.prod(out.shape))


_FLOP_RULES = [
    ((nn.Conv1D, nn.Conv2D, nn.Conv3D), _conv_flops),
    ((nn.Linear,), _linear_flops),
    ((nn.BatchNorm1D, nn.BatchNorm2D, nn.BatchNorm3D, nn.LayerNorm,
      getattr(nn, "GroupNorm", ()), getattr(nn, "RMSNorm", ())),
     _norm_flops),
    ((nn.MaxPool2D, nn.AvgPool2D, nn.AdaptiveAvgPool2D), _pool_flops),
]


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Total forward FLOPs for one batch of ``input_size`` (reference
    `hapi/dynamic_flops.py:flops`). ``custom_ops`` maps layer TYPE to
    ``fn(layer, inputs, output) -> flops``."""
    total = [0]
    detail = []

    def per_layer(layer, name, inputs, outputs):
        out = outputs[0] if isinstance(outputs, (list, tuple)) \
            else outputs
        fn = None
        if custom_ops:
            fn = custom_ops.get(type(layer))
        if fn is None:
            for types, rule in _FLOP_RULES:
                ts = tuple(t for t in (types if isinstance(types, tuple)
                                       else (types,)) if t != ())
                if isinstance(layer, ts):
                    fn = rule
                    break
        n = int(fn(layer, inputs, out)) if fn else 0
        total[0] += n
        detail.append((name, type(layer).__name__, n))

    _run_with_hooks(net, input_size, None, per_layer)
    if print_detail:
        for name, t, n in detail:
            print(f"{name:<40} {t:<20} {n:>14,}")
        print(f"{'Total':<61} {total[0]:>14,}")
    return total[0]
