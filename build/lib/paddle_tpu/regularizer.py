"""Weight-decay regularizers.

Reference: `python/paddle/regularizer.py` (L1Decay / L2Decay). Consumed by
``Optimizer._apply_regularization`` — L2 folds ``coeff * param`` into the
gradient, L1 folds ``coeff * sign(param)``.
"""

__all__ = ["L1Decay", "L2Decay"]


class L1Decay:
    _l1 = True

    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __repr__(self):
        return f"L1Decay(coeff={self.coeff})"


class L2Decay:
    _l1 = False

    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __repr__(self):
        return f"L2Decay(coeff={self.coeff})"
