"""``paddle.sysconfig`` (reference: `python/paddle/sysconfig.py`)."""

import os

__all__ = ["get_include", "get_lib"]

_ROOT = os.path.dirname(os.path.abspath(__file__))


def get_include():
    """Directory of the C++ sources usable as headers (the native
    runtime's src/)."""
    return os.path.join(_ROOT, "native", "src")


def get_lib():
    """Directory containing the built native libraries."""
    return os.path.join(_ROOT, "native", "lib")
