"""``jit.save`` / ``jit.load`` — portable compiled-model export.

Reference: `python/paddle/jit/api.py` ``save``/``load`` +
`jit/translated_layer.py` (``TranslatedLayer`` executing a serialized
program). TPU-native format: the forward is traced to **StableHLO** via
``jax.export`` (shape-polymorphic in every ``None`` dim of the
InputSpec), serialized next to the parameters:

    <path>.pdmodel    serialized StableHLO module (jax.export bytes)
    <path>.pdiparams  parameter arrays (framework io pickle)
    <path>.pdmeta     json: input specs, param names, output treedef

``load`` returns a :class:`TranslatedLayer`: parameters are real Tensors
(swappable / inspectable), and calls execute the deserialized program —
no Python model code needed, the serving deployment path
(reference capability: `fluid/inference/api/analysis_predictor.h:100`).
"""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import export as jax_export

from ..framework.tensor import Tensor, Parameter
from ..framework import io as fio

__all__ = ["save", "load", "TranslatedLayer"]


def _as_specs(input_spec):
    """InputSpec/Tensor/array list -> jax.ShapeDtypeStruct list (None dims
    become export symbols — all in ONE shared scope, since jax.export
    rejects mixing scopes across arguments)."""
    from ..static import InputSpec

    specs = []
    sym_id = 0
    scope = jax_export.SymbolicScope()
    for s in input_spec:
        if isinstance(s, InputSpec):
            dims = []
            for d in s.shape:
                if isinstance(d, str):
                    dims.append(d)        # user-named: shared across inputs
                elif d is None or (isinstance(d, int) and d < 0):
                    dims.append(f"_d{sym_id}")
                    sym_id += 1
                else:
                    dims.append(str(d))
            shape = jax_export.symbolic_shape(",".join(dims), scope=scope) \
                if any(not d.isdigit() for d in dims) \
                else tuple(int(d) for d in dims)
            specs.append(jax.ShapeDtypeStruct(shape, s.dtype))
        elif isinstance(s, Tensor):
            specs.append(jax.ShapeDtypeStruct(s._data.shape,
                                              s._data.dtype))
        else:
            a = np.asarray(s)
            specs.append(jax.ShapeDtypeStruct(a.shape, a.dtype))
    return specs


def save(layer, path, input_spec=None, **configs):
    """Export ``layer``'s forward as StableHLO + params.

    ``input_spec``: list of InputSpec/Tensors describing the forward's
    positional inputs (required for Layers whose forward was never
    shape-specialized).
    """
    from ..nn import Layer
    from ..framework.tensor import no_grad

    if isinstance(layer, Layer):
        fn = type(layer).forward.__get__(layer)
        params = list(layer.parameters())
        # structured state_dict names so a loaded model's set_state_dict
        # interoperates with the original layer's state_dict
        id2name = {id(v): k for k, v in layer.state_dict().items()}
        pnames = [id2name.get(id(p), p.name or f"p{i}")
                  for i, p in enumerate(params)]
    else:
        fn = layer
        params, pnames = [], []
    if input_spec is None:
        raise ValueError("jit.save requires input_spec (shapes/dtypes of "
                         "the forward inputs)")

    out_box = {}

    def pure(param_arrays, *input_arrays):
        saved = [(p._data, p._node) for p in params]
        try:
            for p, a in zip(params, param_arrays):
                p._data = a
                p._node = None
            with no_grad():
                ins = [Tensor(a) for a in input_arrays]
                out = fn(*ins)
            flat, treedef = jax.tree_util.tree_flatten(
                out, is_leaf=lambda x: isinstance(x, Tensor))
            out_box["treedef"] = treedef
            return tuple(o._data if isinstance(o, Tensor) else o
                         for o in flat)
        finally:
            for p, (d, n) in zip(params, saved):
                p._data, p._node = d, n

    pspecs = [jax.ShapeDtypeStruct(p._data.shape, p._data.dtype)
              for p in params]
    ispecs = _as_specs(input_spec)
    exported = jax_export.export(jax.jit(pure))(pspecs, *ispecs)

    dirname = os.path.dirname(path)
    if dirname:
        os.makedirs(dirname, exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    payload = {n: p for n, p in zip(pnames, params)}
    # output pytree structure (dict/nested returns) rides along so load
    # reconstructs the original return shape, not a bare tuple
    payload["__output_treedef__"] = out_box.get("treedef")
    fio.save(payload, path + ".pdiparams")
    meta = {
        "param_names": pnames,
        "inputs": [{"shape": [d if isinstance(d, int) else None
                              for d in getattr(s, "shape", [])],
                    "dtype": str(s.dtype)} for s in ispecs],
        "n_outputs": len(exported.out_avals),
    }
    with open(path + ".pdmeta", "w") as f:
        json.dump(meta, f)
    return path


class TranslatedLayer:
    """A loaded exported model (reference translated_layer.py). Call it
    like the original layer; ``parameters()`` exposes the loaded params."""

    def __init__(self, exported, params, pnames, meta):
        self._exported = exported
        self._params = params
        self._pnames = pnames
        self._meta = meta

    def parameters(self, include_sublayers=True):
        return list(self._params)

    def state_dict(self):
        return {n: p for n, p in zip(self._pnames, self._params)}

    def set_state_dict(self, state):
        matched = 0
        for n, p in zip(self._pnames, self._params):
            if n in state:
                src = state[n]
                p._data = src._data if isinstance(src, Tensor) \
                    else jnp.asarray(src)
                matched += 1
        if state and not matched:
            raise KeyError(
                "set_state_dict matched no parameters; expected keys like "
                f"{self._pnames[:3]}..., got {list(state)[:3]}...")

    def forward(self, *inputs):
        arrays = [i._data if isinstance(i, Tensor) else jnp.asarray(i)
                  for i in inputs]
        outs = self._exported.call([p._data for p in self._params],
                                   *arrays)
        outs = [Tensor(o, stop_gradient=True) for o in outs]
        treedef = self._meta.get("out_treedef")
        if treedef is not None:
            return jax.tree_util.tree_unflatten(treedef, outs)
        return outs[0] if len(outs) == 1 else tuple(outs)

    __call__ = forward

    def eval(self):
        return self

    def train(self):
        raise RuntimeError(
            "TranslatedLayer is an inference program (the exported "
            "StableHLO has no backward); rebuild the python model to "
            "fine-tune")


def load(path, **configs):
    with open(path + ".pdmodel", "rb") as f:
        exported = jax_export.deserialize(f.read())
    with open(path + ".pdmeta") as f:
        meta = json.load(f)
    state = fio.load(path + ".pdiparams")
    meta["out_treedef"] = state.pop("__output_treedef__", None)
    pnames = meta["param_names"]
    params = []
    for n in pnames:
        t = state[n]
        params.append(t if isinstance(t, Tensor) else Tensor(t))
    return TranslatedLayer(exported, params, pnames, meta)
