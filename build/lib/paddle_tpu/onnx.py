"""``paddle.onnx`` (reference: `python/paddle/onnx/export.py` — thin
wrapper over the external ``paddle2onnx`` converter).

Faithful gating: like the reference, ``export`` requires the external
converter and raises ImportError when it is absent (this zero-egress
build cannot install it). The TPU-native export path is
``paddle_tpu.jit.save`` (StableHLO), which XLA-capable runtimes load
directly — preferred over ONNX on TPU serving stacks.
"""

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    try:
        import paddle2onnx  # noqa: F401
    except ImportError:
        raise ImportError(
            "paddle2onnx is required for ONNX export but is not "
            "installed. On TPU prefer paddle_tpu.jit.save(layer, path, "
            "input_spec=...) — StableHLO export, loadable by any "
            "XLA-capable runtime.")
    raise NotImplementedError(
        "paddle2onnx found, but its converter consumes the reference's "
        "Program IR; wire it through jit.save's exported program")
