"""Eager Tensor with define-by-run autograd on top of JAX.

This is the TPU-native analog of the reference's eager layer
(`paddle/fluid/eager`): every op call records a grad node
(`GradNodeBase`, `fluid/eager/grad_node_info.h:197`) whose backward fn is
obtained from ``jax.vjp`` instead of hand-written grad kernels — JAX's AD is
the single source of truth for gradients, mirroring how the reference
generates grad nodes from `backward.yaml` rather than writing them by hand.

Design notes (TPU-first):
- ``Tensor`` wraps a ``jax.Array`` (committed to the default device). All
  compute lowers through jax.numpy → XLA, so eager ops are still
  XLA-executed (dispatched one at a time, like the reference's eager mode
  dispatching one CUDA kernel at a time).
- The same tape works under ``jax.jit`` tracing: ``paddle_tpu.jit.to_static``
  swaps Tensor payloads for tracers and traces imperative user code
  (forward + ``loss.backward()`` + ``opt.step()``) into a single pure XLA
  computation — the analog of the reference's dy2static/SOT capture
  (`python/paddle/jit/`), with no bytecode tricks needed.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import dtype as dtypes
from . import amp_state
from . import enforce
from .. import flags

__all__ = ["Tensor", "Parameter", "GradNode", "is_grad_enabled", "set_grad_enabled",
           "no_grad", "enable_grad", "run_op", "to_tensor"]

# ---------------------------------------------------------------------------
# grad-mode switch (reference: tracer has_grad / paddle.no_grad)
# ---------------------------------------------------------------------------
_grad_enabled = True


def is_grad_enabled() -> bool:
    return _grad_enabled


def set_grad_enabled(mode: bool):
    global _grad_enabled
    prev = _grad_enabled
    _grad_enabled = bool(mode)
    return prev


class _GradModeGuard:
    def __init__(self, mode: bool):
        self._mode = mode
        self._prev = None

    def __enter__(self):
        self._prev = set_grad_enabled(self._mode)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with _GradModeGuard(self._mode):
                return fn(*args, **kwargs)
        return wrapper


def no_grad(fn=None):
    """``paddle.no_grad`` — usable as context manager or decorator."""
    guard = _GradModeGuard(False)
    return guard if fn is None else guard(fn)


def enable_grad(fn=None):
    guard = _GradModeGuard(True)
    return guard if fn is None else guard(fn)


# ---------------------------------------------------------------------------
# Grad node: one per recorded op (reference: GradNodeBase)
# ---------------------------------------------------------------------------
class GradNode:
    __slots__ = ("name", "vjp_fn", "inputs", "n_outputs", "out_avals",
                 "pure_fn", "replay_fn", "__weakref__")

    def __init__(self, name, vjp_fn, inputs, n_outputs, out_avals,
                 pure_fn=None, replay_fn=None):
        self.name = name
        self.vjp_fn = vjp_fn          # tuple-of-cotangents -> tuple-of-input-grads
        self.inputs = inputs          # list[Tensor] — differentiable inputs
        self.n_outputs = n_outputs
        self.out_avals = out_avals    # [(shape, dtype)] for zero-cotangent fill
        self.pure_fn = pure_fn        # pure fn of diff inputs (create_graph replay)
        self.replay_fn = replay_fn    # Tensor-level backward (PyLayer create_graph)

    def __repr__(self):
        return f"<GradNode {self.name} n_in={len(self.inputs)} n_out={self.n_outputs}>"


def _check_nan_inf(name, arrays):
    for a in arrays:
        if isinstance(a, jax.Array) and jnp.issubdtype(a.dtype, jnp.floating):
            if not isinstance(a, jax.core.Tracer) and not bool(jnp.isfinite(a).all()):
                raise FloatingPointError(
                    f"Operator '{name}' output contains NaN/Inf "
                    f"(FLAGS_check_nan_inf is set).")


# ---------------------------------------------------------------------------
# The generic eager-op executor (analog of the generated `*_ad_func` +
# PHI API dispatch path, SURVEY §3.1 steps 2-6).
# ---------------------------------------------------------------------------
def run_op(name, fn, args, kwargs=None, differentiable=True):
    """Execute op ``fn`` (a pure jax function) on mixed Tensor/array args.

    Records a GradNode when grad is enabled and any input Tensor requires
    grad. Returns Tensor or tuple of Tensors, matching fn's output structure.
    """
    kwargs = kwargs or {}
    if amp_state.enabled():
        fn = amp_state.wrap(name, fn)
    diff_tensors = []       # Tensors we differentiate w.r.t.
    spec_args = []          # arg template: ('d', idx) | raw value
    record = _grad_enabled and differentiable

    def scan(v):
        if isinstance(v, Tensor):
            if record and not v.stop_gradient \
                    and jnp.issubdtype(v._data.dtype, jnp.inexact):
                diff_tensors.append(v)
                return ("__diff__", len(diff_tensors) - 1)
            return v._data
        if isinstance(v, (list, tuple)) and any(isinstance(e, Tensor) for e in v):
            return type(v)(scan(e) for e in v)
        return v

    spec_args = [scan(a) for a in args]
    spec_kwargs = {k: scan(v) for k, v in kwargs.items()}

    def substitute(template, diff_arrays):
        def sub(v):
            if isinstance(v, tuple) and len(v) == 2 and v[0] == "__diff__":
                return diff_arrays[v[1]]
            if isinstance(v, (list, tuple)):
                return type(v)(sub(e) for e in v)
            return v
        return [sub(t) for t in template]

    if not diff_tensors:
        raw_args = substitute(spec_args, [])
        raw_kwargs = {k: substitute([v], [])[0] for k, v in spec_kwargs.items()}
        try:
            out = fn(*raw_args, **raw_kwargs)
        except Exception as e:
            raise enforce.attach_op_context(e, name)
        return _wrap_outputs(name, out, stop_gradient=True)

    def pure(*diff_arrays):
        raw_args = substitute(spec_args, diff_arrays)
        raw_kwargs = {k: substitute([v], diff_arrays)[0] for k, v in spec_kwargs.items()}
        return fn(*raw_args, **raw_kwargs)

    primal_arrays = [t._data for t in diff_tensors]
    try:
        out, vjp_fn = jax.vjp(pure, *primal_arrays)
    except Exception as e:
        raise enforce.attach_op_context(e, name)

    is_multi = isinstance(out, (tuple, list))
    outs = list(out) if is_multi else [out]
    out_avals = [(o.shape, o.dtype) for o in outs]
    node = GradNode(name, vjp_fn, diff_tensors, len(outs), out_avals,
                    pure_fn=pure)

    result = _wrap_outputs(name, out, stop_gradient=False)
    rts = result if isinstance(result, tuple) else (result,)
    for i, t in enumerate(rts):
        if jnp.issubdtype(t._data.dtype, jnp.inexact):
            t._node = node
            t._out_index = i
    return result


# observers called as observer(op_name, raw_output) after each op —
# the instrumentation seam the reference codegens into eager ops
# (consumed by paddle_tpu.amp.debugging operator-stats collection)
op_observers = []


def _wrap_outputs(name, out, stop_gradient):
    if flags.flag("check_nan_inf"):
        _check_nan_inf(name, out if isinstance(out, (tuple, list)) else [out])
    for obs in op_observers:
        obs(name, out)
    if isinstance(out, (tuple, list)):
        return tuple(
            Tensor(o, stop_gradient=stop_gradient or not jnp.issubdtype(o.dtype, jnp.inexact))
            for o in out)
    return Tensor(out, stop_gradient=stop_gradient or not jnp.issubdtype(out.dtype, jnp.inexact))


# ---------------------------------------------------------------------------
# Tensor
# ---------------------------------------------------------------------------
class Tensor:
    """Eager tensor. API mirrors ``paddle.Tensor``
    (reference: `paddle/fluid/pybind/eager_method.cc`)."""

    # let Tensor.__r*__ win over numpy array ops
    __array_priority__ = 100

    __slots__ = ("_data", "stop_gradient", "grad", "_node", "_out_index",
                 "name", "persistable", "trainable", "_backward_hooks",
                 "__weakref__", "is_dist", "_placements", "_process_mesh")

    def __init__(self, data, dtype=None, stop_gradient=True, name=None):
        if isinstance(data, Tensor):
            data = data._data
        if not isinstance(data, jax.Array) and not isinstance(data, jax.core.Tracer):
            np_data = np.asarray(data)
            if dtype is None and np_data.dtype == np.float64:
                np_data = np_data.astype(dtypes.get_default_dtype())
            data = jnp.asarray(np_data, dtype=dtypes.convert_dtype(dtype))
        elif dtype is not None:
            d = dtypes.convert_dtype(dtype)
            if data.dtype != d:
                data = data.astype(d)
        self._data = data
        self.stop_gradient = stop_gradient
        self.grad = None
        self._node = None
        self._out_index = 0
        self.name = name
        self.persistable = False
        self.trainable = not stop_gradient
        self._backward_hooks = None
        self.is_dist = False
        self._placements = None
        self._process_mesh = None

    # -- basic properties ---------------------------------------------------
    @property
    def data(self):
        return self

    @data.setter
    def data(self, value):
        self._data = value._data if isinstance(value, Tensor) else jnp.asarray(value)

    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def place(self):
        try:
            devs = self._data.devices()
            return next(iter(devs))
        except Exception:
            return "traced"

    @property
    def T(self):
        from ..tensor import manipulation
        return manipulation.transpose(self, list(range(self.ndim))[::-1])

    def numel(self):
        return self.size

    def dim(self):
        return self.ndim

    # -- conversion ---------------------------------------------------------
    def numpy(self):
        return np.asarray(self._data)

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def item(self, *idx):
        a = self._data
        if idx:
            return a[idx].item() if len(idx) > 1 else a.reshape(-1)[idx[0]].item()
        return a.item()

    def tolist(self):
        return self.numpy().tolist()

    def astype(self, dtype):
        from ..tensor import manipulation
        return manipulation.cast(self, dtype)

    def cast(self, dtype):
        return self.astype(dtype)

    def clone(self):
        from ..tensor import creation
        return creation.assign(self)

    def detach(self):
        t = Tensor(self._data, stop_gradient=True)
        return t

    def detach_(self):
        self._node = None
        self.stop_gradient = True
        return self

    def cpu(self):
        return Tensor(jax.device_get(self._data), stop_gradient=self.stop_gradient)

    def to(self, *args, **kwargs):
        """Move/cast: accepts dtype and/or device specs like ``paddle.Tensor.to``.

        Device moves are recorded on the tape (``jax.device_put`` is
        differentiable), so ``w.to('cpu')`` keeps gradient flow back to ``w``.
        """
        from ..device import _resolve_device, _looks_like_device
        out = self
        for a in list(args) + list(kwargs.values()):
            if a is None:
                continue
            if _looks_like_device(a):
                dev = _resolve_device(str(a))
                out = run_op("to_device",
                             lambda arr: jax.device_put(arr, dev), (out,))
            else:
                try:
                    out = out.astype(a)
                except (TypeError, ValueError):
                    pass
        return out

    def pin_memory(self):
        return self

    def contiguous(self):
        return self

    def is_contiguous(self):
        return True

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        from . import autograd_engine
        autograd_engine.backward([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self, set_to_zero=False):
        if set_to_zero and self.grad is not None:
            self.grad = Tensor(jnp.zeros_like(self.grad._data), stop_gradient=True)
        else:
            self.grad = None

    def zero_(self):
        self._data = jnp.zeros_like(self._data)
        return self

    def register_hook(self, hook):
        if self._backward_hooks is None:
            self._backward_hooks = []
        self._backward_hooks.append(hook)

        class _Handle:
            def __init__(self, hooks, h):
                self._hooks, self._h = hooks, h

            def remove(self):
                if self._h in self._hooks:
                    self._hooks.remove(self._h)
        return _Handle(self._backward_hooks, hook)

    @property
    def is_leaf(self):
        return self._node is None

    def set_value(self, value):
        """In-place payload replacement (optimizer updates use this)."""
        if isinstance(value, Tensor):
            value = value._data
        else:
            value = jnp.asarray(value, dtype=self._data.dtype)
        if tuple(value.shape) != tuple(self._data.shape):
            raise ValueError(
                f"set_value shape mismatch: {value.shape} vs {self._data.shape}")
        self._data = value.astype(self._data.dtype)
        return self

    def get_tensor(self):
        return self

    # -- python protocol ----------------------------------------------------
    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __repr__(self):
        grad_str = "" if self.stop_gradient else ", stop_gradient=False"
        body = repr(self._data) if isinstance(self._data, jax.core.Tracer) \
            else np.array2string(np.asarray(self._data), precision=6, separator=", ")
        return (f"Tensor(shape={self.shape}, dtype={self.dtype}{grad_str},\n"
                f"       {body})")

    def __bool__(self):
        return bool(self._data)

    def __int__(self):
        return int(self._data)

    def __float__(self):
        return float(self._data)

    def __index__(self):
        return int(self._data)

    def __hash__(self):
        return id(self)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __getitem__(self, idx):
        from ..tensor import manipulation
        return manipulation._getitem(self, idx)

    def __setitem__(self, idx, value):
        from ..tensor import manipulation
        manipulation._setitem(self, idx, value)

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.item(), spec)
        return repr(self)

    # arithmetic operators are attached by paddle_tpu.tensor at import time
    # (mirrors the reference's monkey-patching in
    #  python/paddle/base/dygraph/math_op_patch.py)


class Parameter(Tensor):
    """Trainable parameter (reference: ``paddle.base.framework.Parameter``)."""

    __slots__ = ("optimize_attr", "regularizer", "need_clip", "is_distributed")

    def __init__(self, data, dtype=None, name=None, trainable=True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.is_distributed = False

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """``paddle.to_tensor`` equivalent."""
    if isinstance(data, Tensor):
        t = Tensor(data._data, dtype=dtype, stop_gradient=stop_gradient)
        return t
    return Tensor(data, dtype=dtype, stop_gradient=stop_gradient)
