"""``paddle.save`` / ``paddle.load`` (reference: `python/paddle/framework/io.py:725,967`).

Pickle-based object serialization; Tensors are stored as numpy arrays (with
dtype preserved, including bfloat16 via ml_dtypes) and restored as Tensors.
Distributed sharded checkpointing lives in `paddle_tpu.distributed.checkpoint`.
"""

from __future__ import annotations

import os
import pickle

import numpy as np
import jax.numpy as jnp

from .tensor import Tensor, Parameter

__all__ = ["save", "load"]

_PROTO = 4


class _TensorPayload:
    """Pickle-stable tensor container (numpy buffer + dtype string + flags)."""

    __slots__ = ("buffer", "dtype", "shape", "stop_gradient", "is_param", "name")

    def __init__(self, t: Tensor):
        arr = np.asarray(t._data)
        self.dtype = str(t.dtype)
        if arr.dtype == jnp.bfloat16:
            arr = arr.view(np.uint16)
        self.buffer = arr
        self.shape = tuple(t.shape)
        self.stop_gradient = t.stop_gradient
        self.is_param = isinstance(t, Parameter)
        self.name = t.name

    def restore(self) -> Tensor:
        arr = self.buffer
        if self.dtype == "bfloat16":
            arr = jnp.asarray(arr).view(jnp.bfloat16)
        else:
            arr = jnp.asarray(arr)
        if self.is_param:
            t = Parameter(arr, trainable=not self.stop_gradient)
        else:
            t = Tensor(arr, stop_gradient=self.stop_gradient)
        t.name = self.name
        return t


def _pack(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(obj)
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_pack(v) for v in obj)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        t = obj.restore()
        return t.numpy() if return_numpy else t
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=_PROTO, **configs):
    """Save a Tensor / state_dict / nested object to ``path``."""
    if hasattr(obj, "state_dict") and not isinstance(obj, dict):
        obj = obj.state_dict()
    dirname = os.path.dirname(path)
    if dirname:
        os.makedirs(dirname, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    """Load an object saved with ``save``."""
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy=return_numpy)
