"""RNG state management.

Reference: `paddle/phi/core/generator.h` (global + per-device Generator) and
the model-parallel ``RNGStatesTracker`` (`fleet/layers/mpu/random.py:34`).

TPU-native design: state is a JAX PRNG key. Eager ops split the global key.
Under ``jit`` tracing, a traced key is installed with ``rng_guard`` so the
whole program stays functional (the key becomes an input of the compiled
step). Named-state tracking (``rng_state``) gives model-parallel-safe
dropout: each name folds a distinct constant into the key, the analog of the
reference's per-axis seeded states.
"""

from __future__ import annotations

import contextlib
import zlib

import jax
import numpy as np

__all__ = ["seed", "get_rng_state", "set_rng_state", "next_key", "rng_guard",
           "Generator", "default_generator", "rng_state", "fold_in_name"]


class Generator:
    """Stateful PRNG source backed by a JAX key."""

    def __init__(self, seed_val: int = 0):
        self._key = jax.random.key(seed_val)
        self._seed = seed_val

    def manual_seed(self, seed_val: int):
        self._key = jax.random.key(seed_val)
        self._seed = seed_val
        return self

    def initial_seed(self):
        return self._seed

    def get_state(self):
        return self._key

    def set_state(self, state):
        self._key = state

    def next(self):
        self._key, sub = jax.random.split(self._key)
        return sub


default_generator = Generator(np.random.randint(0, 2**31 - 1))

# stack of override generators (installed by rng_guard / rng_state)
_guard_stack: list[Generator] = []


def _current() -> Generator:
    return _guard_stack[-1] if _guard_stack else default_generator


def seed(seed_val: int):
    """``paddle.seed`` — reseed the global generator."""
    default_generator.manual_seed(int(seed_val))
    return default_generator


def get_rng_state():
    return _current().get_state()


def set_rng_state(state):
    _current().set_state(state)


def next_key():
    """Draw a fresh PRNG key from the active generator."""
    return _current().next()


@contextlib.contextmanager
def rng_guard(key):
    """Install ``key`` (possibly a tracer) as the RNG source.

    Used by ``paddle_tpu.jit`` so random ops inside a traced step consume a
    traced key instead of baking host randomness into the compiled program.
    """
    gen = Generator(0)
    gen._key = key
    _guard_stack.append(gen)
    try:
        yield gen
    finally:
        _guard_stack.pop()


def fold_in_name(key, name: str):
    """Deterministically derive a named subkey (stable across processes)."""
    return jax.random.fold_in(key, zlib.crc32(name.encode()) & 0x7FFFFFFF)


@contextlib.contextmanager
def rng_state(name: str = "global"):
    """Model-parallel RNG scope (reference: ``get_rng_state_tracker().rng_state``).

    Inside the scope, keys derive from the active key with ``name`` folded
    in — e.g. tensor-parallel dropout uses a different stream per name while
    staying reproducible.
    """
    base = _current()
    gen = Generator(0)
    gen._key = fold_in_name(base.next(), name)
    _guard_stack.append(gen)
    try:
        yield gen
    finally:
        _guard_stack.pop()
