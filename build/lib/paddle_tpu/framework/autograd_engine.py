"""Reverse-mode autograd engine.

Analog of the reference's queue-based backward runner
(`paddle/fluid/eager/backward.cc` — ``RunBackward`` + ``GeneralGrad`` for
``paddle.grad()``). Works on the GradNode tape recorded by
``framework.tensor.run_op``; each node's backward is a ``jax.vjp`` closure, so
gradients are exactly JAX's gradients.

Engine design:
- iterative DFS topological order (no recursion limit on deep graphs);
- cotangents for non-leaf tensors are keyed by ``(id(node), out_index)`` so
  gathering a node's output grads is O(n_outputs), not a scan over all live
  cotangents — backward is O(edges) overall;
- ``create_graph=True`` replays each node's backward *through the tape*: the
  vjp is re-derived from the node's saved pure function as a differentiable
  op of (primals, cotangents), so grad-of-grad works (the vjp closure alone
  treats primals as constants and would silently drop second-order terms).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .tensor import Tensor, run_op

__all__ = ["backward", "grad"]


def _topo_order(roots):
    """Reverse-topological order of GradNodes reachable from root tensors.

    Iterative DFS with an explicit stack (gray/black marking): graphs deeper
    than Python's recursion limit — long chains from unrolled loops — are
    fine, and diamond-shaped DAGs order correctly.
    """
    visited = set()
    order = []
    stack = [(t._node, False) for t in roots if t._node is not None]
    while stack:
        node, expanded = stack.pop()
        if expanded:
            order.append(node)
            continue
        if id(node) in visited:
            continue
        visited.add(id(node))
        stack.append((node, True))
        for t in node.inputs:
            n = t._node
            if n is not None and id(n) not in visited:
                stack.append((n, False))
    order.reverse()
    return order


def _key(t):
    """Cotangent-store key for a tensor: leaves by identity, non-leaves by
    their (node, output-slot) so lookup during the node sweep is O(1)."""
    if t._node is None:
        return id(t)
    return (id(t._node), t._out_index)


def _run(tensors, grad_tensors, accumulate_into_grad, targets=None,
         retain_graph=False, create_graph=False):
    """Core engine shared by ``Tensor.backward`` and ``paddle.grad``.

    grads accumulate per tensor slot (``_key``), matching the reference's
    ``GradTensorHolder`` multi-path accumulation.
    """
    from .tensor import no_grad

    # cotangent store: _key(tensor) -> jnp array (or Tensor if create_graph)
    cotangents = {}
    leaf_holders = {}  # id -> Tensor (keep leaves alive for .grad writes)

    def _raw(g):
        return g._data if isinstance(g, Tensor) else g

    def _acc(key, g):
        if key in cotangents:
            prev = cotangents[key]
            if create_graph:
                pt = prev if isinstance(prev, Tensor) else Tensor(prev)
                gt = g if isinstance(g, Tensor) else Tensor(g)
                cotangents[key] = run_op("grad_accumulate", jnp.add, (pt, gt))
            else:
                cotangents[key] = prev + _raw(g)
        else:
            cotangents[key] = g

    hook_owners = {}   # _key -> Tensor with registered hooks
    finalized = set()  # keys whose hooks already fired

    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient and t._node is None:
            raise RuntimeError(
                "backward() called on a tensor with stop_gradient=True and no "
                "grad history")
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "grad_tensor must be given for non-scalar outputs "
                    f"(shape {t.shape})")
            g_val = jnp.ones_like(t._data)
        elif create_graph and isinstance(g, Tensor):
            # keep the Tensor so double-backward sees the dependence on the
            # seed (e.g. HVP w.r.t. the vector in grad_outputs)
            g_val = g
        else:
            g_val = g._data if isinstance(g, Tensor) else jnp.asarray(g)
        _acc(_key(t), g_val)
        if t._backward_hooks:
            hook_owners[_key(t)] = t
        if t._node is None:
            leaf_holders[id(t)] = t

    order = _topo_order(tensors)

    def fire_hooks(t, g):
        if t._backward_hooks:
            tg = g if isinstance(g, Tensor) else Tensor(g, stop_gradient=not create_graph)
            for hook in t._backward_hooks:
                r = hook(tg)
                if r is not None:
                    tg = r if isinstance(r, Tensor) else Tensor(r)
            return tg if create_graph else tg._data
        return g

    def _finalize(key, val):
        """Apply tensor hooks once, on the fully-accumulated gradient
        (reference: hooks run on the final grad, not per-edge partials)."""
        owner = hook_owners.get(key)
        if owner is not None and key not in finalized:
            finalized.add(key)
            val = fire_hooks(owner, val)
        return val

    grad_ctx = _null_ctx if create_graph else no_grad

    # snapshot targets as their cotangents complete: a slot's accumulation is
    # final exactly when its producing node is processed (all consumers come
    # earlier in reverse-topo order), and the sweep pops it then.
    results = {}
    target_slots = {}
    if targets is not None:
        for t in targets:
            target_slots.setdefault(_key(t), []).append(id(t))

    def _snapshot(key, val):
        for tid in target_slots.get(key, ()):
            results[tid] = val

    # prune to the useful subgraph when specific targets are requested
    # (reference: GeneralGrad restricts traversal to output->input paths,
    # `fluid/eager/backward.cc:103`). A node is useful iff its backward
    # contributes — directly or through another useful node — to a target.
    useful = None
    if targets is not None:
        target_ids = {id(t) for t in targets}
        useful = set()
        for node in reversed(order):  # leaf-most first
            for t in node.inputs:
                if id(t) in target_ids or (
                        t._node is not None and id(t._node) in useful):
                    useful.add(id(node))
                    break

    with grad_ctx():
        for node in order:
            if useful is not None and id(node) not in useful:
                continue
            # O(1) gather of this node's output cotangents
            outs = []
            any_ct = False
            for i in range(node.n_outputs):
                found = cotangents.pop((id(node), i), None)
                if found is not None:
                    found = _finalize((id(node), i), found)
                    _snapshot((id(node), i), found)
                if found is None:
                    shape, dt = node.out_avals[i]
                    outs.append(jnp.zeros(shape, dt))
                else:
                    any_ct = True
                    outs.append(_raw(found) if not create_graph else found)
            if not any_ct:
                continue
            if node.vjp_fn is _used_up:
                node.vjp_fn()  # raises the freed-graph error
            if create_graph:
                ct_in = _replay_through_tape(node, outs)
            else:
                ct_in = node.vjp_fn(tuple(outs) if node.n_outputs > 1 else outs[0])
            for t, g in zip(node.inputs, ct_in):
                key = _key(t)
                if t._backward_hooks:
                    hook_owners[key] = t
                if t._node is None:
                    leaf_holders[id(t)] = t
                _acc(key, g)
            if not retain_graph:
                node.vjp_fn = _used_up
                node.pure_fn = None    # release saved-forward closures
                node.replay_fn = None

    if targets is not None:
        for t in targets:
            if id(t) in results:
                continue
            val = cotangents.get(_key(t))
            if val is not None:
                results[id(t)] = _finalize(_key(t), val)
        return results

    # write leaf grads
    for tid, t in leaf_holders.items():
        arr = cotangents.get(tid)
        if arr is None:
            continue
        if t._node is None and not t.stop_gradient and accumulate_into_grad:
            arr = _raw(_finalize(tid, arr))
            if t.grad is None:
                t.grad = Tensor(arr, stop_gradient=True)
            else:
                t.grad = Tensor(t.grad._data + arr, stop_gradient=True)
    return results


def _replay_through_tape(node, out_cts):
    """Run a node's backward as differentiable ops so a new tape is recorded.

    The vjp is re-derived from ``node.pure_fn`` (the pure jax function of the
    node's differentiable inputs saved by ``run_op``): as a function of
    (primals, cotangents) it is itself traceable, so second-order grads see
    the full dependence on the primal inputs.
    """
    ct_tensors = [c if isinstance(c, Tensor) else Tensor(c, stop_gradient=True)
                  for c in out_cts]
    if node.pure_fn is None:
        if node.replay_fn is not None:
            # PyLayer: the user backward runs Tensor ops, recording its own tape
            return node.replay_fn(ct_tensors)
        raise NotImplementedError(
            f"create_graph=True through op '{node.name}' is not supported: "
            "the node has no saved forward function or Tensor-level backward.")
    n_in = len(node.inputs)
    multi = node.n_outputs > 1

    def grad_fn(*args):
        primals = args[:n_in]
        cts = args[n_in:]
        _, vjp = jax.vjp(node.pure_fn, *primals)
        return vjp(tuple(cts) if multi else cts[0])

    res = run_op(node.name + "_grad", grad_fn,
                 tuple(node.inputs) + tuple(ct_tensors))
    return res if isinstance(res, tuple) else (res,)


def _used_up(*_):
    raise RuntimeError(
        "Trying to backward through the graph a second time. Set "
        "retain_graph=True when calling backward the first time.")


class _null_ctx:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def backward(tensors, grad_tensors=None, retain_graph=False):
    """``paddle.autograd.backward`` — accumulate into ``.grad`` of leaves."""
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    _run(tensors, grad_tensors, accumulate_into_grad=True,
         retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """``paddle.grad`` — return grads of ``inputs`` without touching ``.grad``.

    Reference: ``GeneralGrad`` in `fluid/eager/backward.cc:103`.
    """
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]
    if retain_graph is None:
        retain_graph = create_graph
    res = _run(outputs, grad_outputs, accumulate_into_grad=False,
               targets=inputs, retain_graph=retain_graph,
               create_graph=create_graph)
    out = []
    for t in inputs:
        if id(t) in res:
            v = res[id(t)]
            if isinstance(v, Tensor):
                out.append(v)
            else:
                out.append(Tensor(v, stop_gradient=not create_graph))
        else:
            if not allow_unused:
                raise RuntimeError(
                    "One of the input tensors was not used in the graph "
                    "(pass allow_unused=True to return None for it).")
            out.append(None)
    return out
