"""Framework core: dtype system, Tensor, autograd engine, RNG.

The analog of the reference's `paddle/phi/core` + `paddle/fluid/eager`
(SURVEY §2.1, §2.3) — except the device runtime is PJRT via JAX and
gradients come from `jax.vjp` instead of generated grad kernels.
"""

from . import dtype  # noqa: F401  (module; the class is dtype.dtype)
from .dtype import (  # noqa: F401
    convert_dtype, get_default_dtype, set_default_dtype,
    is_floating_point_dtype, iinfo, finfo,
)
from .tensor import (  # noqa: F401
    Tensor, Parameter, to_tensor, no_grad, enable_grad,
    is_grad_enabled, set_grad_enabled,
)
from . import random  # noqa: F401
from .random import seed, get_rng_state, set_rng_state  # noqa: F401
from . import autograd_engine  # noqa: F401


def in_dynamic_mode():
    return True


def in_pir_mode():
    return False


def in_dynamic_or_pir_mode():
    return True
