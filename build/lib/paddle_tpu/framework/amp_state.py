"""Autocast state + per-op cast wrapping for the eager executor.

Reference: the AMP branch the reference's codegen emits into every eager op
(`paddle/fluid/eager/amp_auto_cast.h`, driven by the op lists in
`python/paddle/amp/amp_lists.py`). Here the policy is applied at the single
dispatch seam (`framework.tensor.run_op`): white-list ops cast their
floating inputs to the autocast dtype (bf16 on TPU — the MXU's native
format), black-list ops cast to float32, everything else runs in whatever
dtype its inputs already have. The cast happens *inside* the op's pure
function, so it is differentiated by ``jax.vjp`` (cotangents cast back
automatically) and traces cleanly under ``jit``.

This module holds only the mutable state and the cast transform; the user
API lives in ``paddle_tpu.amp``.
"""

from __future__ import annotations

import jax
import numpy as np

__all__ = ["AmpAttrs", "current", "push", "pop", "enabled", "wrap"]

_CASTABLE = ("float16", "bfloat16", "float32")


class AmpAttrs:
    __slots__ = ("dtype", "level", "white", "black")

    def __init__(self, dtype, level, white, black):
        self.dtype = np.dtype(dtype)
        self.level = level
        self.white = frozenset(white)
        self.black = frozenset(black)


_stack: list[AmpAttrs] = []


def current():
    return _stack[-1] if _stack else None


def push(attrs):
    _stack.append(attrs)


def pop():
    return _stack.pop()


def enabled():
    return bool(_stack)


def _cast(v, target):
    if isinstance(v, (jax.Array, jax.core.Tracer)) \
            and v.dtype.name in _CASTABLE and v.dtype != target:
        return v.astype(target)
    if isinstance(v, (list, tuple)):
        return type(v)(_cast(e, target) for e in v)
    return v


def wrap(name, fn):
    """Return ``fn`` with autocast input casting for op ``name`` (identity
    when the op is in neither list)."""
    st = current()
    if st is None:
        return fn
    if name in st.white:
        target = st.dtype
    elif name in st.black:
        target = np.dtype("float32")
    else:
        return fn

    def casted(*args, **kwargs):
        args = tuple(_cast(a, target) for a in args)
        kwargs = {k: _cast(v, target) for k, v in kwargs.items()}
        return fn(*args, **kwargs)

    return casted
