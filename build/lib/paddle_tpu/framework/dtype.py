"""Dtype system.

Maps the reference's ``paddle.dtype`` surface (phi ``DataType``,
`paddle/phi/common/data_type.h`) onto JAX dtypes. Dtypes are plain
``jnp.dtype`` objects so they interoperate directly with jax/numpy.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

__all__ = [
    "float16", "float32", "float64", "bfloat16",
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64",
    "bool_", "complex64", "complex128",
    "dtype", "convert_dtype", "get_default_dtype", "set_default_dtype",
    "is_floating_point_dtype", "iinfo", "finfo",
]

dtype = jnp.dtype

float16 = jnp.dtype(jnp.float16)
float32 = jnp.dtype(jnp.float32)
float64 = jnp.dtype(jnp.float64)
bfloat16 = jnp.dtype(jnp.bfloat16)
int8 = jnp.dtype(jnp.int8)
int16 = jnp.dtype(jnp.int16)
int32 = jnp.dtype(jnp.int32)
int64 = jnp.dtype(jnp.int64)
uint8 = jnp.dtype(jnp.uint8)
uint16 = jnp.dtype(jnp.uint16)
uint32 = jnp.dtype(jnp.uint32)
uint64 = jnp.dtype(jnp.uint64)
bool_ = jnp.dtype(jnp.bool_)
complex64 = jnp.dtype(jnp.complex64)
complex128 = jnp.dtype(jnp.complex128)

_ALIASES = {
    "float16": float16, "fp16": float16, "half": float16,
    "float32": float32, "fp32": float32, "float": float32,
    "float64": float64, "fp64": float64, "double": float64,
    "bfloat16": bfloat16, "bf16": bfloat16,
    "int8": int8, "int16": int16, "int32": int32, "int64": int64,
    "uint8": uint8, "uint16": uint16, "uint32": uint32, "uint64": uint64,
    "bool": bool_, "complex64": complex64, "complex128": complex128,
}

_default_dtype = float32


def convert_dtype(d) -> jnp.dtype:
    """Normalize any dtype spec (str / np / jnp / paddle-style) to jnp.dtype.

    TPU-first: when JAX runs in its default 32-bit regime, 64-bit requests
    canonicalize to 32-bit (int32 indices are what the TPU wants; the
    reference defaults to int64/float64 on CPU but we do not follow that).
    """
    if d is None:
        return None
    if isinstance(d, str):
        key = d.lower()
        d = _ALIASES[key] if key in _ALIASES else jnp.dtype(d)
    else:
        d = jnp.dtype(d)
    import jax
    if not jax.config.jax_enable_x64:
        d = _X64_DOWN.get(d, d)
    return d


_X64_DOWN = {float64: float32, int64: int32, uint64: uint32,
             complex128: complex64}


def default_int() -> jnp.dtype:
    return convert_dtype(int64)


def get_default_dtype() -> jnp.dtype:
    return _default_dtype


def set_default_dtype(d) -> None:
    global _default_dtype
    d = convert_dtype(d)
    if d not in (float16, float32, float64, bfloat16):
        raise TypeError(f"default dtype must be floating point, got {d}")
    _default_dtype = d


def is_floating_point_dtype(d) -> bool:
    return jnp.issubdtype(convert_dtype(d), jnp.floating)


def iinfo(d):
    return jnp.iinfo(convert_dtype(d))


def finfo(d):
    return jnp.finfo(convert_dtype(d))
