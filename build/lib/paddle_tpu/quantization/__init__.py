"""``paddle.quantization`` — QAT / PTQ.

Reference: `python/paddle/quantization/` (``QuantConfig``, ``QAT.quantize``
fake-quant wrapping, ``PTQ`` observer calibration, ``convert`` to the
deployed int8 form) with observers in `quantization/observers/` and
quanters in `quanters/`.

TPU-native mechanics: fake-quantization is a pure jnp round-to-grid with
a straight-through estimator (``jax.custom_vjp`` identity gradient), so
QAT steps stay one fused XLA program; ``convert`` stores int8 weights +
fp scales and dequantizes on the fly (int8 x bf16 upcasts ride the MXU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .. import nn
from ..framework.tensor import Parameter, Tensor, run_op

__all__ = ["BaseObserver", "AbsmaxObserver", "PerChannelAbsmaxObserver",
           "FakeQuanterWithAbsMax", "QuantConfig", "QAT", "PTQ",
           "QuantedLinear", "quant_dequant"]


# ---------------------------------------------------------------------------
# fake quant with straight-through estimator
# ---------------------------------------------------------------------------
@functools.lru_cache(maxsize=8)
def _ste_fn(bits):
    qmax = float(2 ** (bits - 1) - 1)

    @jax.custom_vjp
    def fq(x, scale):
        s = jnp.maximum(scale, 1e-9)
        return jnp.clip(jnp.round(x / s * qmax), -qmax, qmax) * s / qmax

    def fwd(x, scale):
        return fq(x, scale), None

    def bwd(_, g):
        return g, None          # straight-through: d(fq)/dx ~= 1

    fq.defvjp(fwd, bwd)
    return fq


def quant_dequant(x, scale, bits=8):
    """Tape-integrated fake quantization (STE gradient)."""
    return run_op("quant_dequant",
                  lambda a, s: _ste_fn(bits)(a, s), (x, scale))


# ---------------------------------------------------------------------------
# observers (reference observers/abs_max.py)
# ---------------------------------------------------------------------------
class BaseObserver:
    def __init__(self, quant_bits=8):
        self.quant_bits = quant_bits
        self._scale = None

    def observe(self, x):
        raise NotImplementedError

    def scale(self):
        if self._scale is None:
            raise RuntimeError("observer has seen no data")
        return self._scale


class AbsmaxObserver(BaseObserver):
    """Running per-tensor absmax."""

    def observe(self, x):
        arr = x.numpy() if isinstance(x, Tensor) else np.asarray(x)
        m = float(np.abs(arr).max()) if arr.size else 0.0
        self._scale = m if self._scale is None else max(self._scale, m)
        return self._scale


class PerChannelAbsmaxObserver(BaseObserver):
    """Per-output-channel absmax (weights; channel axis = last)."""

    def __init__(self, quant_bits=8, channel_axis=-1):
        super().__init__(quant_bits)
        self.channel_axis = channel_axis

    def observe(self, x):
        arr = x.numpy() if isinstance(x, Tensor) else np.asarray(x)
        axes = tuple(i for i in range(arr.ndim)
                     if i != (self.channel_axis % arr.ndim))
        m = np.abs(arr).max(axis=axes)
        self._scale = m if self._scale is None \
            else np.maximum(self._scale, m)
        return self._scale


class FakeQuanterWithAbsMax:
    """Quanter factory used by QuantConfig (reference
    quanters/abs_max.py): per-call absmax scale during QAT."""

    def __init__(self, quant_bits=8):
        self.quant_bits = quant_bits

    def __call__(self, x):
        def fn(a):
            s = jnp.max(jnp.abs(a))
            return _ste_fn(self.quant_bits)(a, s)

        return run_op("fake_quant_absmax", fn, (x,))


# ---------------------------------------------------------------------------
# config + quantized layers
# ---------------------------------------------------------------------------
class QuantConfig:
    """Reference quantization/config.py. ``activation``/``weight`` are
    quanter factories applied to every matched layer."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation or FakeQuanterWithAbsMax(8)
        self.weight = weight or FakeQuanterWithAbsMax(8)
        self._types = (nn.Linear,)
        self._per_type = {}   # layer type -> (activation, weight)

    def add_type_config(self, layer_types, activation=None, weight=None):
        if not isinstance(layer_types, (list, tuple)):
            layer_types = [layer_types]
        for t in layer_types:
            if not (isinstance(t, type) and issubclass(t, nn.Linear)):
                raise NotImplementedError(
                    f"quantization of {getattr(t, '__name__', t)} is not "
                    "supported yet (only Linear-family layers); the "
                    "QuantedLinear wrapper computes F.linear")
            self._per_type[t] = (activation, weight)
        self._types = tuple(set(self._types) | set(layer_types))

    def quanters_for(self, layer):
        for t, (a, w) in self._per_type.items():
            if isinstance(layer, t):
                return (a or self.activation, w or self.weight)
        return (self.activation, self.weight)


class QuantedLinear(nn.Layer):
    """Linear with fake-quantized weights + activations (QAT form)."""

    def __init__(self, linear, config):
        super().__init__()
        self.weight = linear.weight
        self.bias = linear.bias
        self._act_q, self._w_q = config.quanters_for(linear)

    def forward(self, x):
        xq = self._act_q(x)
        wq = self._w_q(self.weight)
        from ..nn import functional as F
        return F.linear(xq, wq, self.bias)


class ConvertedLinear(nn.Layer):
    """Deployed int8 form: int8 weights + fp32 scale, dequant on use.
    With a calibrated ``act_scale`` (PTQ), inputs are snapped to the int8
    grid too, matching the deployed runtime's numerics."""

    def __init__(self, weight_i8, scale, bias, act_scale=None):
        super().__init__()
        self.register_buffer("weight_int8", Tensor(weight_i8))
        self.register_buffer("weight_scale", Tensor(scale))
        self.bias = bias
        self.act_scale = None if act_scale is None \
            else Tensor(np.float32(act_scale))

    def forward(self, x):
        act_scale = self.act_scale

        def fn(xa, wi8, s, b, a_s):
            if a_s is not None:
                xa = _ste_fn(8)(xa, a_s)
            w = wi8.astype(jnp.float32) * (s / 127.0)
            y = xa @ w
            return y + b if b is not None else y

        return run_op("int8_linear", fn,
                      (x, self.weight_int8, self.weight_scale, self.bias,
                       act_scale))


def _replace_sublayers(model, predicate, build):
    for name, sub in list(model._sub_layers.items()):
        if predicate(sub):
            model._sub_layers[name] = build(sub)
        else:
            _replace_sublayers(sub, predicate, build)
    return model


def _maybe_copy(model, inplace):
    if inplace:
        return model
    import copy
    return copy.deepcopy(model)


class QAT:
    """Quantization-aware training driver (reference qat.py)."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model, inplace=False):
        cfg = self.config
        return _replace_sublayers(
            _maybe_copy(model, inplace),
            lambda l: isinstance(l, cfg._types),
            lambda l: QuantedLinear(l, cfg))

    def convert(self, model, inplace=False):
        return _convert(_maybe_copy(model, inplace))


class PTQ:
    """Post-training quantization: calibrate observers, then convert —
    convert() bakes each observed layer's activation scale into its
    deployed form."""

    def __init__(self, config: QuantConfig = None):
        self.config = config or QuantConfig()
        self._observers = {}

    def quantize(self, model, inplace=False):
        model = _maybe_copy(model, inplace)
        # attach activation observers via forward hooks
        for name, sub in model.named_sublayers(include_self=False):
            if isinstance(sub, self.config._types):
                obs = AbsmaxObserver()
                self._observers[name] = obs

                def hook(lyr, inputs, o=obs):
                    o.observe(inputs[0])
                    return None   # observe only — never replace inputs

                sub.register_forward_pre_hook(hook)
        return model

    def convert(self, model, inplace=False):
        model = _maybe_copy(model, inplace)
        scales = {}
        for name, obs in self._observers.items():
            try:
                scales[name] = float(obs.scale())
            except RuntimeError:
                pass  # never calibrated: weight-only for this layer
        return _convert(model, act_scales=scales)


def _convert(model, act_scales=None):
    act_scales = act_scales or {}
    names = {id(sub): name
             for name, sub in model.named_sublayers(include_self=False)}

    def build(l):
        w = l.weight.numpy()
        scale = np.abs(w).max() or 1.0
        wi8 = np.clip(np.round(w / scale * 127.0), -127, 127) \
            .astype(np.int8)
        return ConvertedLinear(wi8, np.float32(scale), l.bias,
                               act_scale=act_scales.get(names.get(id(l))))

    return _replace_sublayers(
        model, lambda l: isinstance(l, (nn.Linear, QuantedLinear)), build)


# -- weight-only quant ops (reference ops `weight_quantize`,
#    `weight_dequantize`, `weight_only_linear`, `llm_int8_linear` —
#    `phi/kernels/gpu/weight_only_linear_kernel.cu`) ------------------------
from ..tensor.registry import defop as _defop


@_defop(name="weight_quantize", differentiable=False)
def weight_quantize(x, algo="weight_only_int8"):
    """Per-out-channel abs-max int8 quantization of a [in, out] weight.
    Returns (int8 weight, float scale [out])."""
    if algo not in ("weight_only_int8", "llm.int8"):
        raise ValueError(f"unsupported algo {algo!r}")
    scale = jnp.max(jnp.abs(x), axis=0) / 127.0
    q = jnp.clip(jnp.round(x / jnp.maximum(scale, 1e-12)), -127, 127) \
        .astype(jnp.int8)
    return q, scale.astype(jnp.float32)


@_defop(name="weight_dequantize", differentiable=False)
def weight_dequantize(x, scale, algo="weight_only_int8"):
    return x.astype(jnp.float32) * scale[None, :]


@_defop(name="weight_only_linear")
def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8"):
    """y = x @ dequant(W) (+ b): weights stay int8 in HBM (half the
    bandwidth of bf16 — the decode bottleneck), dequantized on the fly
    in the matmul's epilogue (XLA fuses the scale multiply)."""
    w = weight.astype(x.dtype)
    if weight_scale is not None:
        y = jnp.matmul(x, w) * weight_scale[None, :].astype(x.dtype)
    else:
        y = jnp.matmul(x, w)
    if bias is not None:
        y = y + bias
    return y


@_defop(name="llm_int8_linear")
def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold=6.0):
    """LLM.int8() linear (reference op `llm_int8_linear`): columns of
    ``x`` with outlier magnitude > threshold run in full precision,
    the rest through the int8 path."""
    w = weight.astype(jnp.float32)
    if weight_scale is not None:
        w = w * weight_scale[None, :]
    # With the weight dequantized to fp32 the reference's outlier split
    # (int8 path for calm columns, fp path for outliers) is numerically
    # a single matmul — one MXU pass, same result.
    y = jnp.matmul(x.astype(jnp.float32), w).astype(x.dtype)
    if bias is not None:
        y = y + bias
    return y
